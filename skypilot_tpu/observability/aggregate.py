"""Metrics federation: scrape-and-merge of Prometheus exposition
across every process of the fleet.

PR 1 gave each process a local registry on its own ``GET /metrics``;
this module is the aggregation tier above them — the in-tree analogue
of Prometheus *federation* (hierarchical scrape-and-merge of exposition
families). The API server scrapes every known endpoint (model-server
replicas, load balancers, skylet/controller exposition files) and
serves ONE merged exposition at ``GET /metrics/fleet``, so a single
scrape target covers the fleet.

Merge semantics (the part naive concatenation gets wrong):

  * **counters** and **histograms** sum across instances — a fleet-wide
    ``rate()`` over the merged family equals the sum of per-instance
    rates;
  * **gauges** (and untyped families) must NOT sum — "last tick
    timestamp" or "slots active" summed across replicas is meaningless
    — so each sample keeps its source under an added ``instance=``
    label;
  * **histogram bucket mismatches** (two replicas declaring different
    ``le`` ladders, e.g. across a rolling update that changed bucket
    config) are detected and REPORTED, never silently summed — the
    family falls back to instance-labeled samples and the snapshot
    carries the error;
  * **type conflicts** (one instance says counter, another gauge) skip
    the family with an error.

Everything here is stdlib-only and built on ``metrics.parse_exposition``
— the same wire format production Prometheus would scrape, not a
side-channel JSON.
"""

from __future__ import annotations

import dataclasses
import os
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

from skypilot_tpu.observability import metrics

# The exposition file daemons without an HTTP surface (skylet, serve
# controller) write atomically each tick; the rpc ``get_metrics`` method
# and local-home federation both read it.
METRICS_FILENAME = "metrics.prom"

# Instance label added to gauge samples (and to everything in a family
# that could not be merged). Collides with nothing: the registry's own
# families never carry it.
INSTANCE_LABEL = "instance"

_SUMMED_TYPES = ("counter", "histogram")


def endpoint(component: str, instance: str, *,
             url: Optional[str] = None,
             path: Optional[str] = None,
             get_text: Optional[Callable[[], str]] = None,
             stale_after_s: Optional[float] = None) -> Dict[str, Any]:
    """One federation target. Exactly one source:

    * ``url`` — HTTP GET (a process's ``/metrics``);
    * ``path`` — an exposition file (skylet/controller ticks write one);
      ``stale_after_s`` marks the target down when the file is older;
    * ``get_text`` — in-process callable (the API server's own registry,
      rendered at scrape time so the snapshot is fresh).
    """
    if sum(x is not None for x in (url, path, get_text)) != 1:
        raise ValueError("endpoint needs exactly one of url/path/get_text")
    return {"component": component, "instance": instance, "url": url,
            "path": path, "get_text": get_text,
            "stale_after_s": stale_after_s}


def scrape(ep: Dict[str, Any], timeout: float = 2.0
           ) -> Tuple[Optional[Dict[str, dict]], Optional[str]]:
    """Fetch + parse one endpoint. Returns ``(families, None)`` or
    ``(None, error)`` — a down component must never fail the whole
    federation pass."""
    try:
        if ep.get("get_text") is not None:
            text = ep["get_text"]()
        elif ep.get("path") is not None:
            stale = ep.get("stale_after_s")
            if stale is not None:
                age = time.time() - os.path.getmtime(ep["path"])
                if age > stale:
                    return None, f"exposition file stale ({age:.0f}s old)"
            with open(ep["path"], encoding="utf-8") as f:
                text = f.read()
        else:
            with urllib.request.urlopen(ep["url"], timeout=timeout) as r:
                text = r.read().decode("utf-8", errors="replace")
        return metrics.parse_exposition(text), None
    except Exception as e:  # noqa: BLE001 — one dead target != no fleet
        return None, f"{type(e).__name__}: {e}"


@dataclasses.dataclass
class FleetSnapshot:
    """One federation pass: merged families + per-target status."""

    ts: float
    families: Dict[str, dict]
    targets: List[Dict[str, Any]]   # component/instance/ok/error
    errors: List[str]               # merge-level problems (mismatches)

    def render(self) -> str:
        """Merged families as Prometheus text exposition (plus
        synthesized ``skytpu_fleet_scrape_up`` per-target liveness and
        ``skytpu_fleet_merge_errors`` samples)."""
        fams = dict(self.families)
        fams["skytpu_fleet_scrape_up"] = {
            "type": "gauge",
            "samples": [({"component": t["component"],
                          INSTANCE_LABEL: t["instance"]},
                         1.0 if t["ok"] else 0.0)
                        for t in self.targets]}
        fams["skytpu_fleet_merge_errors"] = {
            "type": "gauge",
            "samples": [({}, float(len(self.errors)))]}
        return render_families(fams)


def _series_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


def _merge_summed(per_instance: List[Tuple[str, dict]], ftype: str,
                  errors: List[str], name: str) -> Optional[List[tuple]]:
    """Sum counter/histogram samples across instances by label set.
    Returns None (caller falls back to instance labeling) on a
    histogram bucket mismatch."""
    if ftype == "histogram":
        # Bucket-ladder check first: the set of `le` bounds per series
        # (labels minus le/__name__) must agree across instances.
        ladders: Dict[tuple, Dict[str, set]] = {}
        for inst, fam in per_instance:
            for labels, _ in fam["samples"]:
                if not labels.get("__name__", "").endswith("_bucket"):
                    continue
                base = {k: v for k, v in labels.items()
                        if k not in ("le", "__name__")}
                ladders.setdefault(_series_key(base), {}).setdefault(
                    inst, set()).add(labels.get("le", ""))
        for key, by_inst in ladders.items():
            distinct = {frozenset(s) for s in by_inst.values()}
            if len(distinct) > 1:
                errors.append(
                    f"histogram bucket mismatch in {name}"
                    f"{dict(key) or ''}: instances "
                    f"{sorted(by_inst)} declare different `le` ladders"
                    f" — kept per-instance, not summed")
                return None
    summed: Dict[tuple, float] = {}
    order: List[tuple] = []
    keyed_labels: Dict[tuple, Dict[str, str]] = {}
    for _, fam in per_instance:
        for labels, value in fam["samples"]:
            key = _series_key(labels)
            if key not in summed:
                summed[key] = 0.0
                order.append(key)
                keyed_labels[key] = dict(labels)
            summed[key] += value
    return [(keyed_labels[k], summed[k]) for k in order]


def merge(sources: List[Tuple[Dict[str, Any], Dict[str, dict]]]
          ) -> Tuple[Dict[str, dict], List[str]]:
    """Merge per-instance family dicts (``parse_exposition`` output)
    into one fleet-wide dict. Returns ``(families, errors)``."""
    by_name: Dict[str, List[Tuple[str, dict]]] = {}
    for ep, fams in sources:
        for name, fam in fams.items():
            by_name.setdefault(name, []).append((ep["instance"], fam))
    merged: Dict[str, dict] = {}
    errors: List[str] = []
    for name, per_instance in by_name.items():
        types = {fam["type"] for _, fam in per_instance}
        if len(types) > 1:
            errors.append(
                f"type conflict in {name}: {sorted(types)} across "
                f"instances {sorted(i for i, _ in per_instance)} — "
                f"family skipped")
            continue
        ftype = types.pop()
        if ftype in _SUMMED_TYPES:
            samples = _merge_summed(per_instance, ftype, errors, name)
            if samples is not None:
                merged[name] = {"type": ftype, "samples": samples}
                continue
            # Bucket mismatch: fall through to instance labeling so the
            # data stays visible, just not aggregated.
        samples = []
        for inst, fam in per_instance:
            for labels, value in fam["samples"]:
                labeled = dict(labels)
                labeled[INSTANCE_LABEL] = inst
                samples.append((labeled, value))
        merged[name] = {"type": ftype, "samples": samples}
    return merged, errors


def render_families(families: Dict[str, dict]) -> str:
    """Families (``parse_exposition`` shape) back to text exposition.
    Sample-name labels (``__name__`` from histogram children) render as
    the sample's own name, so the output round-trips through
    ``parse_exposition`` again."""
    lines: List[str] = []
    for name in sorted(families):
        fam = families[name]
        lines.append(f"# TYPE {name} {fam['type']}")
        for labels, value in fam["samples"]:
            labels = dict(labels)
            sample_name = labels.pop("__name__", name)
            pairs = sorted(labels.items())
            label_s = ""
            if pairs:
                inner = ",".join(
                    f'{k}="{metrics._escape_label(v)}"'
                    for k, v in pairs)
                label_s = "{" + inner + "}"
            lines.append(
                f"{sample_name}{label_s} {metrics._format_value(value)}")
    return "\n".join(lines) + ("\n" if lines else "")


def federate(endpoints: List[Dict[str, Any]],
             timeout: float = 2.0) -> FleetSnapshot:
    """One federation pass over ``endpoints``. Scrapes run on a small
    thread pool — they are independent I/O, and a sequential pass over
    N unreachable targets would block N x timeout exactly when the
    fleet view matters most (an outage)."""
    import concurrent.futures
    if len(endpoints) > 1:
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(8, len(endpoints))) as pool:
            results = list(pool.map(
                lambda ep: scrape(ep, timeout=timeout), endpoints))
    else:
        results = [scrape(ep, timeout=timeout) for ep in endpoints]
    sources: List[Tuple[Dict[str, Any], Dict[str, dict]]] = []
    targets: List[Dict[str, Any]] = []
    for ep, (fams, err) in zip(endpoints, results):
        targets.append({"component": ep["component"],
                        "instance": ep["instance"],
                        "ok": fams is not None, "error": err})
        if fams is not None:
            sources.append((ep, fams))
    families, errors = merge(sources)
    return FleetSnapshot(ts=time.time(), families=families,
                         targets=targets, errors=errors)


def discover_endpoints(self_text: Optional[Callable[[], str]] = None,
                       host: str = "127.0.0.1") -> List[Dict[str, Any]]:
    """The fleet as this host knows it: the caller's own registry,
    every serve service's load balancer + READY/NOT_READY replicas,
    per-service controller exposition files, and skylet exposition
    files of clusters whose head dir is under this home (the local
    provider and the controller host itself; remote heads surface via
    the rpc ``get_metrics`` method instead)."""
    eps: List[Dict[str, Any]] = []
    if self_text is not None:
        eps.append(endpoint("api-server", "self", get_text=self_text))
    try:
        from skypilot_tpu.serve import serve_state
        from skypilot_tpu.utils import paths
        for svc in serve_state.list_services():
            if svc["status"].is_terminal():
                continue
            name = svc["name"]
            if svc.get("lb_port"):
                eps.append(endpoint(
                    "load-balancer", name,
                    url=f"http://{host}:{svc['lb_port']}/metrics"))
            ctrl_path = os.path.join(
                paths.home(), f"serve-metrics-{name}.prom")
            if os.path.exists(ctrl_path):
                eps.append(endpoint("serve-controller", name,
                                    path=ctrl_path, stale_after_s=60.0))
            for r in serve_state.list_replicas(name):
                if r["url"] and r["status"].value in ("READY",
                                                      "NOT_READY"):
                    eps.append(endpoint(
                        "model-server", f"{name}/{r['replica_id']}",
                        url=f"{r['url']}/metrics"))
    except Exception:  # noqa: BLE001 — no serve DB yet is normal
        pass
    try:
        from skypilot_tpu.utils import paths
        clusters_root = os.path.join(paths.home(), "clusters")
        if os.path.isdir(clusters_root):
            from skypilot_tpu.observability import health
            for cname in sorted(os.listdir(clusters_root)):
                cdir = os.path.join(clusters_root, cname)
                p = os.path.join(cdir, METRICS_FILENAME)
                # Only skylets EXPECTED to be alive federate: a skylet
                # that exited by design (unarmed / autostop fired)
                # leaves a frozen heartbeat behind that would breach
                # the staleness rule forever. No stale_after on the
                # survivors: a WEDGED skylet's old heartbeat gauge is
                # exactly what that rule alerts on — dropping the file
                # would mask the breach.
                if os.path.exists(p) and health.skylet_expected(cdir):
                    eps.append(endpoint("skylet", cname, path=p))
    except OSError:
        pass
    return eps


# ---------------------------------------------------------------------------
# Snapshot math shared by the SLO watchdog and `skytpu top`.

def sample_value(families: Dict[str, dict], name: str,
                 match: Optional[Dict[str, str]] = None,
                 sample_name: Optional[str] = None,
                 agg: str = "sum") -> Optional[float]:
    """Aggregate matching samples of one family (``sum``, ``max``, or
    ``min``); None when nothing matches. ``match`` filters on label
    equality; ``sample_name`` selects histogram children
    (``x_count``/``x_sum``)."""
    fam = families.get(name)
    if fam is None:
        return None
    vals = []
    for labels, value in fam["samples"]:
        if sample_name is not None and \
                labels.get("__name__", name) != sample_name:
            continue
        if sample_name is None and "__name__" in labels:
            continue
        if match and any(labels.get(k) != v for k, v in match.items()):
            continue
        vals.append(value)
    if not vals:
        return None
    if agg == "max":
        return max(vals)
    if agg == "min":
        return min(vals)
    return sum(vals)


def delta(prev: Optional[Dict[str, dict]], cur: Dict[str, dict],
          name: str, match: Optional[Dict[str, str]] = None,
          sample_name: Optional[str] = None) -> Optional[float]:
    """Counter increase between two snapshots, clamped at zero — a
    counter reset mid-window (process restart) must read as "no
    increase", not a huge negative rate."""
    cur_v = sample_value(cur, name, match, sample_name)
    if cur_v is None:
        return None
    prev_v = sample_value(prev, name, match, sample_name) \
        if prev is not None else None
    if prev_v is None:
        return max(cur_v, 0.0)
    return max(cur_v - prev_v, 0.0)


def filtered_delta(prev: Optional[Dict[str, dict]],
                   cur: Dict[str, dict], name: str,
                   match: Callable[[Dict[str, str]], bool]
                   ) -> Optional[float]:
    """Counter increase summed over label sets accepted by ``match``,
    clamped per series — one restarted replica's reset must not erase
    the other replicas' increase. Shared by the SLO rule engine and
    `skytpu top`'s rate columns."""
    fam = cur.get(name)
    if fam is None:
        return None
    prev_fam = prev.get(name) if prev is not None else None
    prev_by_key: Dict[tuple, float] = {}
    if prev_fam is not None:
        for labels, value in prev_fam["samples"]:
            if "__name__" in labels:
                continue
            prev_by_key[_series_key(labels)] = value
    total, seen = 0.0, False
    for labels, value in fam["samples"]:
        if "__name__" in labels:
            continue
        if not match(labels):
            continue
        seen = True
        total += max(value - prev_by_key.get(_series_key(labels), 0.0),
                     0.0)
    return total if seen else 0.0


def histogram_quantile(prev: Optional[Dict[str, dict]],
                       cur: Dict[str, dict], name: str,
                       q: float) -> Optional[float]:
    """Prometheus-style quantile over the bucket *increase* between two
    snapshots (or the cumulative counts when ``prev`` is None).
    Linear interpolation within the winning bucket; the +Inf bucket
    answers with the highest finite bound."""
    fam = cur.get(name)
    if fam is None or fam["type"] != "histogram":
        return None
    buckets: Dict[str, float] = {}
    for labels, value in fam["samples"]:
        if labels.get("__name__", "").endswith("_bucket"):
            le = labels.get("le", "")
            buckets[le] = buckets.get(le, 0.0) + value
    if prev is not None:
        prev_fam = prev.get(name)
        if prev_fam is not None:
            for labels, value in prev_fam["samples"]:
                if labels.get("__name__", "").endswith("_bucket"):
                    le = labels.get("le", "")
                    if le in buckets:
                        buckets[le] -= value
    def _bound(le: str) -> float:
        return float("inf") if le == "+Inf" else float(le)
    ladder = sorted(buckets.items(), key=lambda kv: _bound(kv[0]))
    if not ladder:
        return None
    # Clamp per-bucket negatives (a replica reset between snapshots).
    cum = [max(v, 0.0) for _, v in ladder]
    total = cum[-1]
    if total <= 0:
        return None
    rank = q * total
    prev_bound, prev_cum = 0.0, 0.0
    for (le, _), c in zip(ladder, cum):
        bound = _bound(le)
        if c >= rank:
            if bound == float("inf"):
                return prev_bound if prev_bound > 0 else None
            if c == prev_cum:
                return bound
            return (prev_bound
                    + (bound - prev_bound) * (rank - prev_cum)
                    / (c - prev_cum))
        prev_bound, prev_cum = bound, c
    return prev_bound if prev_bound > 0 else None
